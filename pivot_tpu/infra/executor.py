"""Callback-based task executor — the cluster side of dispatch, flattened.

The reference executes every task instance as its own SimPy process
(``resources/__init__.py:119-135``: one ``_execute_task`` process wrapping
``Host.execute``, itself yielding through admission → staging barrier →
compute timeout).  This framework's ``process`` executor mirrors that shape
on the in-house kernel; it is faithful but pays generator machinery — a
``Process`` object, a bootstrap event, an ``any_of``/``all_of`` event pair,
and several resume round-trips — for **every one of the ~433k task
instances** in a full Alibaba trace window.

``FastExecutor`` keeps the observable semantics and the timing arithmetic
bit-identical while driving each execution with bare callbacks instead:

  * admission, meter check-in, and predecessor sampling run synchronously
    at dispatch (same instant, same RNG draw order as ``Host.execute``);
  * the staging barrier is a countdown object handed to ``Route.send`` in
    place of an ``Event`` — each chunk-service completion decrements it
    inside the route's own callback, with zero extra heap traffic;
  * compute is one ``schedule_callback(runtime)`` whose conclusion performs
    release / check-out / ``notify_q.put`` — one heap event per execution.

Host state (capacity vectors, resident-task sets) and every meter hook stay
on the Python objects, so the invariant auditor (``infra.audit``), the
dense exports (``Cluster.availability_matrix``), and all metrics observe
identical state at identical sim times.  Full-simulation bit parity with
the ``process`` executor is asserted in ``tests/test_executor.py``.

**Event-hop parity** (the subtle part): in the process executor a
completion at time T performs its release two event hops after the compute
timeout fires — the timeout event (scheduled at compute start, old seq)
carries no state change; the ``any_of`` race event it triggers gets a
*fresh* seq at T, so every event already pending at T with an older seq —
most importantly a scheduler tick scheduled at T−interval — observes host
state *before* the release.  The fast executor reproduces this exactly:
the compute timer fires a no-op hop whose only job is to schedule the
actual conclusion as a fresh zero-delay callback.  The admission-failure
notification is likewise deferred one hop to sit where the process
executor's bootstrap event would.

Fault semantics match ``Host.execute``'s abort race (``infra.faults``):
``abort_host`` cancels pending staging transfers (data already on the wire
finishes its chunk), closes the meter interval without refunding capacity
— the machine is gone; ``recover`` resets it wholesale — and surfaces each
resident task as ``(False, task)`` on ``notify_q`` for the retry loop.  A
compute completion due at or before the crash instant wins the tie (the
process executor's timeout event, with its older seq, fires before the
crash-triggered abort event resolves the race), so ``abort_host`` skips
executions whose conclusion is already due.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from pivot_tpu.utils import LogMixin

__all__ = ["FastExecutor"]


class _StageDone:
    """Countdown token passed to ``Route.send`` instead of an ``Event``.

    Routes only ever call ``.succeed()`` on their completion hook (and
    ``cancel`` compares identity), so this quacks enough — and the
    decrement runs inside the route's chunk callback with no extra heap
    event, where the process executor pays a done-event → ``all_of`` →
    ``any_of`` → resume chain per predecessor transfer.
    """

    __slots__ = ("ex",)

    def __init__(self, ex: "_Exec"):
        self.ex = ex

    def succeed(self, value=None, priority=None):
        ex = self.ex
        ex.staging_remaining -= 1
        if ex.staging_remaining == 0 and not ex.aborted:
            ex.executor._staging_complete(ex)


class _Exec:
    """One in-flight task execution."""

    __slots__ = (
        "executor",
        "task",
        "host",
        "preds",
        "routes",
        "dones",
        "pull_start",
        "staging_remaining",
        "aborted",
        "conclude_at",
    )

    def __init__(self, executor: "FastExecutor", task, host):
        self.executor = executor
        self.task = task
        self.host = host
        self.preds: List = []
        self.routes: List = []
        self.dones: List[_StageDone] = []
        self.pull_start = 0.0
        self.staging_remaining = 0
        self.aborted = False
        self.conclude_at: Optional[float] = None


class FastExecutor(LogMixin):
    """Flattened executor for one cluster (``Cluster(executor='fast')``)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.env = cluster.env
        # host id -> {task: exec}, insertion-ordered like Host._aborts so
        # abort order under a crash matches the process executor.
        self._resident: Dict[str, Dict[object, _Exec]] = {}

    # -- dispatch (synchronous, called from Cluster._dispatch_loop) -------
    def dispatch(self, task, host) -> None:
        """Admit and start ``task`` on ``host``; failures notify immediately.

        Mirrors ``Host.execute`` (ref ``resources/__init__.py:244-314``)
        step for step: liveness + all-or-nothing admission, meter check-in,
        predecessor sampling (same ``cluster.pyrng`` draw order), staging
        sends in predecessor order, then the compute timer.
        """
        env, meter, cluster = self.env, host.meter, self.cluster
        group = task.group
        if not host.up or not host.resource.try_acquire(
            group.cpus, group.mem, group.disk, group.gpus
        ):
            cluster.notify_q.put((False, task))
            return

        host._tasks.add(task)
        ex = _Exec(self, task, host)
        self._resident.setdefault(host.id, {})[task] = ex
        if meter:
            meter.host_check_in(host)
        task.set_running()

        ex.pull_start = env.now
        preds = host._sample_predecessor_inputs(task)
        if preds:
            ex.preds = preds
            ex.staging_remaining = len(preds)
            for p in preds:
                route = cluster.get_route(host._output_source(p, cluster), host.id)
                ex.routes.append(route)
                done = _StageDone(ex)
                ex.dones.append(done)
                route.send(p.output_size, done)
        else:
            self._start_compute(ex)

    # -- staging barrier → compute ----------------------------------------
    def _staging_complete(self, ex: _Exec) -> None:
        host = ex.host
        if host.meter:
            host._record_transfer(ex.task, ex.preds, ex.routes, ex.pull_start)
        self._start_compute(ex)

    def _start_compute(self, ex: _Exec) -> None:
        # Straggler fault model (``infra.faults.slow_host``): compute
        # started while the host straggles is stretched by the current
        # multiplier; in-flight compute keeps its original finish time
        # (the timer is already on the heap).  slowdown == 1.0 when
        # healthy, and x * 1.0 == x bitwise — the no-straggler
        # trajectory is unchanged, same as ``Host.execute``.
        duration = ex.task.runtime * ex.host.slowdown
        ex.conclude_at = self.env.now + duration
        self.env.schedule_callback(duration, lambda: self._compute_done(ex))

    def _compute_done(self, ex: _Exec) -> None:
        # No-op hop mirroring the process executor's timeout event: the
        # release happens one fresh-seq event later, so anything already
        # pending at this instant (a scheduler tick above all) sees host
        # state before the release — see the module docstring.
        if ex.aborted:
            return
        self.env.schedule_callback(0.0, lambda: self._conclude(ex))

    def _conclude(self, ex: _Exec) -> None:
        if ex.aborted:
            return
        task, host = ex.task, ex.host
        group = task.group
        host.resource.release(group.cpus, group.mem, group.disk, group.gpus)
        host._tasks.discard(task)
        live = self._resident.get(host.id)
        if live is not None:
            live.pop(task, None)
            if not live:
                del self._resident[host.id]
        if host.meter:
            host.meter.host_check_out(host)
        # Drop the staging graph: metered Transfers are retained as meter
        # keys for the whole run and reach this _Exec via their done hooks;
        # clearing the lists keeps the retained residue per transfer small.
        ex.preds = ex.routes = ex.dones = ()
        self.cluster.notify_q.put((True, task))

    # -- faults ------------------------------------------------------------
    def _abort_exec(self, ex, task, host, now: float) -> None:
        """Shared crash/eviction teardown for one resident execution:
        cancel staging, close the meter interval, bill the wasted work as
        rework, surface ``(False, task)`` to the governed retry loop."""
        ex.aborted = True
        for route, done in zip(ex.routes, ex.dones):
            route.cancel(done)
        host._tasks.discard(task)
        if host.meter:
            host.meter.host_check_out(host)
            host.meter.add_rework(now - ex.pull_start)
        ex.preds = ex.routes = ex.dones = ()
        self.cluster.notify_q.put((False, task))

    def abort_host(self, host) -> None:
        """Host crashed: abort every resident execution (``Host.fail``)."""
        live = self._resident.pop(host.id, None)
        if not live:
            return
        now = self.env.now
        for task, ex in live.items():
            if ex.conclude_at is not None and ex.conclude_at <= now:
                # Completion already due: the process executor's timeout
                # event outruns the abort race — let the conclusion land.
                self._resident.setdefault(host.id, {})[task] = ex
                continue
            self._abort_exec(ex, task, host, now)

    def evict_task(self, task, host) -> bool:
        """Proactively abort ONE resident execution on a LIVE host — the
        spot-drain restart path (``GlobalScheduler.on_preempt_warning``).
        Unlike :meth:`abort_host`, the machine keeps running, so the
        task's capacity IS refunded; the execution aborts exactly like a
        crash otherwise (staging cancelled, meter interval closed, the
        wasted work billed as rework, ``(False, task)`` surfaced for the
        governed retry loop).  Returns False — and touches nothing —
        when the task is not live here or its conclusion is already due
        (evicting a completed execution would turn a free success into a
        retry)."""
        live = self._resident.get(host.id)
        ex = live.get(task) if live else None
        if ex is None or ex.aborted or not host.up:
            return False
        now = self.env.now
        if ex.conclude_at is not None and ex.conclude_at <= now:
            return False
        group = task.group
        host.resource.release(group.cpus, group.mem, group.disk, group.gpus)
        live.pop(task, None)
        if not live:
            del self._resident[host.id]
        self._abort_exec(ex, task, host, now)
        return True

    def evict_doomed(self, host, deadline: float) -> List:
        """Evict every resident execution that provably cannot conclude
        before ``deadline`` (the preemption abort instant): compute-phase
        executions with ``conclude_at`` past it, and staging executions
        whose compute alone would overrun.  Residents that fit inside
        the lead are left to drain out.  Returns the evicted tasks."""
        live = self._resident.get(host.id)
        if not live:
            return []
        now = self.env.now
        doomed = []
        for task, ex in list(live.items()):
            if ex.aborted:
                continue
            if ex.conclude_at is None:
                eta = now + task.runtime * host.slowdown
            else:
                eta = ex.conclude_at
            if eta > deadline and self.evict_task(task, host):
                doomed.append(task)
        return doomed

    # -- introspection -----------------------------------------------------
    def resident(self, host) -> List[Tuple[object, bool]]:
        """(task, staging_done) for executions live on ``host``."""
        return [
            (t, ex.staging_remaining == 0)
            for t, ex in self._resident.get(host.id, {}).items()
        ]
