"""Roofline accounting for the placement kernels (VERDICT r05 gap #2).

Every bench figure so far was *relative* ("38,498× the naive twin") with
no absolute grounding — nobody could say whether 60 M decisions/s is 5%
or 60% of what the chip allows.  This module supplies the absolute side:

  * **per-kernel work models** — analytic FLOP and HBM-byte estimates per
    placement-kernel call as a function of the (T-bucket, H, R) shape
    (:func:`placement_cost`), with the counting rules documented inline;
  * **per-backend peak tables** — the CPU's peaks are *measured once per
    process* by a STREAM-style triad probe (bandwidth) and a BLAS GEMM
    probe (FLOPs) (:func:`cpu_peaks`); the TPU's come from the known v5e
    chip spec (:data:`TPU_PEAKS`);
  * **row annotation** — :func:`annotate` turns (shape, measured seconds)
    into achieved GFLOP/s / GB/s and %-of-peak columns for the
    ``BENCH_*.json`` schema, plus a ``bound`` verdict;
  * **serialization model** — :func:`serial_model` prices a scan-form
    kernel as ``steps × per-step seconds`` (the per-step cost is measured
    by ``bench.py`` with a short-T probe at the same H).  When the
    roofline bounds predict a wall far below the measured one and the
    serial model lands within ~2×, the kernel is *serialization-bound* —
    the round-5 headline's missing explanation.

All numbers are estimates for trend-level accounting, not a simulator:
the work models count the dominant dense ops (compares, multiplies,
selects over the [T, H] decision space) and charge bytes for the arrays
a step genuinely touches, assuming loop carries stay resident (registers
/ cache / VMEM — true for every kernel form in ``ops/kernels.py`` and
``ops/pallas_kernels.py``).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

__all__ = [
    "PALLAS_PROVEN_HP",
    "PALLAS_VMEM_BUDGET_BYTES",
    "TPU_PEAKS",
    "V5E_SCOPED_VMEM_BYTES",
    "annotate",
    "backend_peaks",
    "cpu_peaks",
    "fused_loop_model",
    "placement_cost",
    "serial_model",
]

# -- v5e VMEM budget constants (single source of truth) ----------------------
#
# The Pallas kernels (``ops/pallas_kernels.py``) size their replica
# blocks against these, and the ``pallas-budget`` static pass
# (``pivot_tpu/analysis/pallas_budget.py``) recomputes every kernel's
# VMEM footprint from its BlockSpec shapes and fails the build when a
# tile change outgrows them — so the numbers live HERE, once, not in a
# kernel comment that can drift.

#: Scoped-VMEM capacity one Pallas program may allocate on a v5e core
#: (Mosaic's scoped-allocation limit; exceeding it is a hardware-proven
#: compile failure — RB=1024 at Hp=512, RESULTS.md round 3).
V5E_SCOPED_VMEM_BYTES = int(16e6)

#: Working-set budget the replica-block auto-sizer targets — deliberate
#: headroom under :data:`V5E_SCOPED_VMEM_BYTES` for Mosaic's own
#: pipeline buffers and the semaphore/metadata overhead the block
#: accounting cannot see.
PALLAS_VMEM_BUDGET_BYTES = int(12e6)

#: Hardware-proven host-lane envelope of the replica-batched greedy
#: kernel (every RB sweep in RESULTS.md ran at Hp ≤ 512).  The static
#: budget pass verifies the footprint inside this envelope; larger host
#: counts rely on the runtime auto-sizer shrinking RB and are outside
#: the verified envelope.
PALLAS_PROVEN_HP = 512

#: Known-chip peak table.  v5e figures from the public spec: 197 TFLOP/s
#: bf16 on the MXUs and 819 GB/s of HBM bandwidth per chip.  The f32
#: vector peak is derived, not published: the VPU issues over (8, 128)
#: lanes with an FMA per lane per cycle at the ~1.5 GHz clock implied by
#: the MXU spec (197e12 / (4 MXUs · 128·128 MACs · 2)), giving
#: 8·128·2·1.5e9 ≈ 3.1 TFLOP/s.  The placement kernels are VPU-shaped
#: (elementwise compares/selects + small reductions), so ``flops_peak``
#: uses the VPU figure — quoting the MXU peak would understate achieved
#: fraction ~64× for work that cannot use the MXU.
TPU_PEAKS: Dict[str, Dict[str, float]] = {
    "v5e": {
        "bw_gbps": 819.0,
        "flops_peak_gflops": 3_100.0,  # VPU f32 (derived — see above)
        "mxu_bf16_gflops": 197_000.0,
        "source": "public v5e spec; VPU f32 derived from clock",
    },
}

_CPU_PEAKS_CACHE: Optional[Dict[str, float]] = None


def cpu_peaks(force: bool = False) -> Dict[str, float]:
    """One-shot measured CPU peaks: STREAM-triad bandwidth + GEMM FLOPs.

    Triad ``a = b + s·c`` over 2²² f64 per array, best of 3.  numpy
    cannot fuse it, so it runs as two ops (``a = 3·c`` then
    ``a = a + b``) touching FIVE 8-byte slots per element — read c,
    write a, read a, read b, write a — and the bandwidth figure counts
    all five (counting the classic fused-triad 3 would understate the
    peak ~40% and flip ``annotate``'s bound verdicts).  GEMM (512³ f64
    ``np.dot``, best of 3) counts 2·n³ FLOPs and measures whatever BLAS
    the numpy in this image carries — the honest ceiling for dense f64
    compute here.  Cached per process (~0.2 s once).
    """
    global _CPU_PEAKS_CACHE
    if _CPU_PEAKS_CACHE is not None and not force:
        return _CPU_PEAKS_CACHE
    n = 1 << 22
    b = np.random.default_rng(0).random(n)
    c = np.random.default_rng(1).random(n)
    a = np.empty_like(b)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np.multiply(c, 3.0, out=a)
        np.add(a, b, out=a)
        best = min(best, time.perf_counter() - t0)
    bw_gbps = 5 * 8 * n / best / 1e9  # 5 accesses/element — see docstring
    m = 512
    x = np.random.default_rng(2).random((m, m))
    y = np.random.default_rng(3).random((m, m))
    np.dot(x, y)  # warm
    bestg = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np.dot(x, y)
        bestg = min(bestg, time.perf_counter() - t0)
    _CPU_PEAKS_CACHE = {
        "bw_gbps": round(bw_gbps, 2),
        "flops_peak_gflops": round(2 * m**3 / bestg / 1e9, 2),
        "source": "measured: STREAM-style triad + f64 GEMM probe",
    }
    return _CPU_PEAKS_CACHE


def backend_peaks(backend: str, chip: str = "v5e") -> Dict[str, float]:
    """Peak table for a JAX backend name ("cpu" probes, "tpu" looks up)."""
    if backend == "tpu":
        return TPU_PEAKS[chip]
    return cpu_peaks()


def placement_cost(
    kind: str,
    T: int,
    H: int,
    R: int = 1,
    dtype_bytes: int = 8,
    n_groups: Optional[int] = None,
) -> Dict[str, float]:
    """Estimated (flops, bytes) of ONE placement-kernel call.

    Counting rules (per task step over H hosts, 4 resource dims; compares
    and selects count as 1 op — they occupy the same vector issue slots
    as arithmetic):

      * fit test: 4H compares + 3H ANDs ≈ 7H
      * group-score row (cost-aware): 4H mul + 3H add + H sqrt + 2H div
        ≈ 10H — charged per STEP for the scan form (it recomputes the
        row under a select every step) but per GROUP for slim/chunked
        (phase 2 computes it at entries only)
      * masked argmin (or rank-select): ≈ 3H
      * availability update: ≈ 8·4 (scatter) — negligible vs the rows

    Bytes charge what a step streams when carries stay resident: the
    two [H] score-table rows it gathers (scan) plus the [H, 4]
    availability working set ONCE per call (it lives in
    registers/cache/VMEM across steps), and for chunked forms the
    [C, H, 4] prefix stack write+read.  ``R`` scales replicas (vmapped
    scan / pallas_rb share one task stream).

    kinds: "scan" | "slim" | "chunked" | "pallas_rb" (same model as
    "scan" with the score row charged per step — the Pallas kernel also
    recomputes it under ``pl.when`` — but zero per-step table gathers:
    phase-1 tiles stream once).
    """
    G = n_groups if n_groups is not None else max(T // 16, 1)
    fit = 7.0 * H
    score_row = 10.0 * H
    argmin = 3.0 * H
    place = 32.0
    if kind in ("scan", "pallas_rb"):
        per_task = fit + score_row + argmin + place
        flops = R * T * per_task
        gathers = 2 * H * dtype_bytes  # cost + bw rows per step
        if kind == "pallas_rb":
            gathers = 0  # phase-1 tiles stream once, charged below
        bytes_ = (
            R * T * gathers
            + R * 8 * H * dtype_bytes      # avail in + out, once per call
            + T * (2 * H) * dtype_bytes    # phase-1 tiles / tables, once
        )
    elif kind == "slim":
        flops = R * (T * (fit + argmin + place) + G * score_row)
        # Like the flops rule, table-row bytes are charged per GROUP: the
        # slim pass gathers the score rows only at group entries (the
        # per-step streams are the [4] demand + scalars — negligible).
        bytes_ = R * (
            G * 2 * H * dtype_bytes        # table rows per group entry
            + 8 * H * dtype_bytes
        )
    elif kind == "chunked":
        # spec + recheck ≈ 2 decision passes + the [C, H, 4] prefix
        # stack traffic (write in the fold, read in the recheck).
        flops = R * (T * 2 * (fit + argmin + place) + G * score_row)
        bytes_ = R * (
            T * 8 * H * dtype_bytes        # prefix stack write + read
            + 8 * H * dtype_bytes
        )
    else:
        raise ValueError(f"unknown kernel kind {kind!r}")
    return {"flops": float(flops), "bytes": float(bytes_)}


def annotate(
    seconds: float,
    kind: str,
    T: int,
    H: int,
    R: int = 1,
    backend: str = "cpu",
    dtype_bytes: int = 8,
    n_groups: Optional[int] = None,
    peaks: Optional[Dict[str, float]] = None,
) -> Dict[str, float]:
    """Roofline columns for one bench row: estimated work, achieved
    GFLOP/s / GB/s, %-of-peak for both, and which bound (if any) binds.

    ``bound`` is "compute" or "bandwidth" when the achieved fraction
    exceeds 33% of that peak; otherwise "serialization" — neither
    roofline explains the wall, the sequential chain does (pair with
    :func:`serial_model`).
    """
    peaks = peaks or backend_peaks(backend)
    cost = placement_cost(kind, T, H, R, dtype_bytes, n_groups)
    gflops = cost["flops"] / seconds / 1e9
    gbs = cost["bytes"] / seconds / 1e9
    pf = gflops / peaks["flops_peak_gflops"]
    pb = gbs / peaks["bw_gbps"]
    if pf >= max(pb, 0.33):
        bound = "compute"
    elif pb >= 0.33:
        bound = "bandwidth"
    else:
        bound = "serialization"
    return {
        "kind": kind,
        "est_flops": cost["flops"],
        "est_bytes": cost["bytes"],
        "achieved_gflops": round(gflops, 3),
        "achieved_gbs": round(gbs, 3),
        "pct_peak_flops": round(100 * pf, 3),
        "pct_peak_bw": round(100 * pb, 3),
        "bound": bound,
    }


def serial_model(n_steps: int, step_seconds: float) -> Dict[str, float]:
    """Serialization price of a scan-form kernel: ``n_steps`` dependent
    iterations at the measured per-step wall (``bench.py`` probes it with
    a short-T run at the same H).  If this lands within ~2× of the
    measured call, the kernel is serialization-bound — the chain, not
    the rooflines, sets the wall."""
    return {
        "n_steps": int(n_steps),
        "step_us": round(step_seconds * 1e6, 3),
        "predicted_s": round(n_steps * step_seconds, 6),
    }


def fused_loop_model(
    n_ticks: int,
    tick_seconds: float,
    dispatch_floor_s: float,
) -> Dict[str, float]:
    """Dispatch-amortization model of the fused tick driver
    (``ops/tickloop.py``): a span of ``n_ticks`` simulator ticks pays the
    fixed per-call dispatch floor ONCE, where the per-tick path pays it
    every tick — the fused-loop extension of :func:`serial_model` (which
    prices only the in-call serial chain).

      predicted wall(K)          = floor + K · tick_seconds
      predicted per-tick overhead = floor / K

    ``tick_seconds`` is the marginal device cost of ONE simulated tick
    (measured by a two-point difference over span lengths, so the floor
    cancels — the ``_scan_step_probe`` idiom); ``dispatch_floor_s`` is
    the probe-measured per-call round trip.  ``bench.py``'s
    ``fused_tick`` row pairs these predictions with measured walls per
    K — the predicted-vs-measured column of the round-8 acceptance
    criterion (per-tick overhead amortizing toward zero as K grows).
    """
    predicted = dispatch_floor_s + n_ticks * tick_seconds
    return {
        "n_ticks": int(n_ticks),
        "tick_us": round(tick_seconds * 1e6, 3),
        "dispatch_floor_us": round(dispatch_floor_s * 1e6, 3),
        "predicted_s": round(predicted, 9),
        "predicted_per_tick_s": round(predicted / n_ticks, 9),
        "predicted_overhead_per_tick_us": round(
            dispatch_floor_s / n_ticks * 1e6, 3
        ),
    }
