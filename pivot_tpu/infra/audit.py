"""Simulation-state invariant auditor — the framework's sanitizer.

The reference has **no race detection or sanitizers** (SURVEY.md §5); its
concurrency safety rests on SimPy's cooperative scheduling plus a mutex.
This framework's cooperative kernel gives the same atomicity, but resource
accounting bugs (double release, leaked admission, negative capacity,
ghost tasks on dead hosts) would corrupt results *silently* — placements
still happen, metrics still print.  The auditor makes the invariants
explicit and checkable at any dispatch point:

  * per host, per dimension: ``0 ≤ available ≤ total`` (up hosts);
  * the sum of resident tasks' demands equals the capacity in use;
  * down hosts hold no tasks (tasks whose abort has fired but not yet
    been delivered are tolerated — a legitimate transient between the
    failure event and the aborted process resuming);
  * down hosts report the −1 availability sentinel in
    ``availability_matrix`` (what keeps fit masks off them);
  * a Python-backend route is busy iff it has a transfer in service
    (native routes keep their queue in the C++ engine and are skipped).

Run it ad hoc (``violations = audit_cluster(cluster)``), or install it as
a kernel step observer (``start_periodic_audit``) to fail fast at the
first corrupted state — the DES analog of running under a sanitizer.
The observer never schedules events, so it cannot advance sim time or
change any metric.

Round 7 adds the **conservation and billing audits** the chaos soak is
refereed by: :func:`audit_conservation` (every admitted task terminates
exactly once — completed, dead-lettered, or cancelled with its failed
app — and no placement ever landed on a down or quarantined host) and
:func:`audit_meter` (busy-interval/billing well-formedness under any
fault schedule), combined by :func:`audit_run`.
"""

from __future__ import annotations

from typing import List

__all__ = [
    "AuditError",
    "audit_cluster",
    "audit_conservation",
    "audit_meter",
    "audit_run",
    "audit_serve",
    "check",
    "start_periodic_audit",
]

#: Relative tolerance for float accounting (fractional trace demands
#: accumulate rounding on acquire/release).
_RTOL = 1e-6


class AuditError(AssertionError):
    """Raised by the periodic auditor on the first invariant violation."""


def _close(a: float, b: float, scale: float) -> bool:
    return abs(a - b) <= _RTOL * max(scale, 1.0)


def check(cluster, context: str) -> None:
    """Audit and raise :class:`AuditError` (with ``context`` in the
    message) on any violation — the single raise path shared by the
    periodic observer and end-of-run checks."""
    violations = audit_cluster(cluster)
    if violations:
        raise AuditError(
            f"simulation state corrupted ({context}):\n  "
            + "\n  ".join(violations)
        )


def audit_cluster(cluster) -> List[str]:
    """Check every invariant; return human-readable violations (empty = ok)."""
    from pivot_tpu.infra.network import NativeRoute

    violations: List[str] = []
    dims = ("cpus", "mem", "disk", "gpus")
    avail_mat = None
    for hi, host in enumerate(cluster.hosts):
        r = host.resource
        if not host.up:
            # In-flight completions that legitimately outlive the crash:
            # process executor — aborts already triggered in Host._aborts;
            # fast executor — due-completion tie-breaks kept resident by
            # abort_host for their one-hop conclusion (executor.py).
            fast_live = (
                {t for t, _staged in cluster.executor.resident(host)}
                if cluster.executor is not None
                else set()
            )
            stuck = [
                t for t in host._tasks
                if t not in fast_live
                and not (t in host._aborts and host._aborts[t].triggered)
            ]
            if stuck:
                violations.append(
                    f"{host.id}: down but holds {len(stuck)} task(s) with "
                    "no abort in flight"
                )
            if avail_mat is None:
                avail_mat = cluster.availability_matrix()
            if not (avail_mat[hi] == -1.0).all():
                violations.append(
                    f"{host.id}: down but availability row is "
                    f"{avail_mat[hi].tolist()}, not the -1 sentinel"
                )
            continue
        in_use = [0.0, 0.0, 0.0, 0.0]
        for task in host._tasks:
            g = task.group
            for i, d in enumerate((g.cpus, g.mem, g.disk, g.gpus)):
                in_use[i] += d
        for i, dim in enumerate(dims):
            avail = getattr(r, dim)
            total = getattr(r, "t_" + dim)
            if avail < -_RTOL * max(total, 1.0):
                violations.append(f"{host.id}: negative {dim} ({avail})")
            if avail > total * (1 + _RTOL):
                violations.append(
                    f"{host.id}: {dim} available {avail} exceeds total {total}"
                )
            if not _close(total - avail, in_use[i], total):
                violations.append(
                    f"{host.id}: {dim} in use {total - avail:.6g} != "
                    f"Σ resident demands {in_use[i]:.6g}"
                )
    for key, route in cluster._routes.items():
        if isinstance(route, NativeRoute):
            continue  # queue state lives in the C++ engine
        if route._busy != (route._in_service is not None):
            violations.append(
                f"route {key}: busy={route._busy} but "
                f"in_service={route._in_service!r}"
            )
    return violations


def start_periodic_audit(cluster, period: float = 5.0) -> None:
    """Audit at event boundaries, at most once per ``period`` sim-seconds;
    raise :class:`AuditError` with the full violation list the first time
    any invariant breaks.

    Installed as a kernel step observer (``Environment.add_step_observer``)
    rather than as heap events: the audit piggybacks on real events, so it
    cannot advance sim time past the last workload event, keep ``run()``
    alive, or perturb event ordering."""
    env = cluster.env
    last = [env.now]

    def _observe():
        if env.now - last[0] < period:
            return
        last[0] = env.now
        check(cluster, f"t={env.now:.3f}")

    env.add_step_observer(_observe)


# ---------------------------------------------------------------------------
# Conservation + billing audits (round 7 — the chaos soak's referee)
# ---------------------------------------------------------------------------


def audit_conservation(scheduler, apps) -> List[str]:
    """Task-conservation law under retry governance (``sched/retry.py``):
    after a run drains, every materialized task of every submitted app
    terminates **exactly once** —

      * a finished app: all tasks FINISHED, none dead-lettered;
      * a failed app: every task FINISHED (completed before the failure),
        DEAD (exactly the dead-letter queue's entries), or NASCENT
        (cancelled with the app — never placed again);
      * no task both finished and dead-lettered, no task left in the
        SUBMITTED/RUNNING limbo states;
      * each DEAD task has one dead-letter record, budget-exhausted
        entries consumed exactly ``max_retries + 1`` attempts, and no
        placement ever landed on a down or quarantined host
        (``scheduler.placement_violations``).

    Returns human-readable violations (empty = the law holds).
    """
    violations: List[str] = []
    # Keyed by (app, task): task ids are group-local ("src/1") and
    # collide across apps — a bare-task_id ledger would count app A's
    # dead letter against app B's finished twin of the same name.
    dead_ids = {}
    for entry in scheduler.dead_letters:
        key = (entry.app_id, entry.task_id)
        if key in dead_ids:
            violations.append(
                f"task {entry.task_id} (app {entry.app_id}): multiple "
                "dead-letter records (terminated more than once)"
            )
        dead_ids[key] = entry
    retry = scheduler.retry
    if retry is not None:
        for entry in scheduler.dead_letters:
            budget = retry.budget(getattr(entry, "tier", 0))
            if entry.reason == "retry_budget" and budget is not None and (
                entry.attempts != budget + 1
            ):
                violations.append(
                    f"task {entry.task_id}: dead-lettered after "
                    f"{entry.attempts} attempts, tier budget says "
                    f"{budget + 1}"
                )
    seen_dead = set()
    for app in apps:
        failed = bool(getattr(app, "failed", False))
        if failed and app.is_finished:
            violations.append(f"app {app.id}: both failed and finished")
        for group in app.groups:
            for task in group.tasks:
                state = task.state.value
                key = (app.id, task.id)
                if task.is_dead:
                    seen_dead.add(key)
                    if key not in dead_ids:
                        violations.append(
                            f"task {task.id}: DEAD with no dead-letter record"
                        )
                    if not failed:
                        violations.append(
                            f"task {task.id}: dead-lettered but app "
                            f"{app.id} not marked failed"
                        )
                elif task.is_finished:
                    if key in dead_ids:
                        violations.append(
                            f"task {task.id} (app {app.id}): both finished "
                            "and dead-lettered"
                        )
                elif state in ("submitted", "running"):
                    violations.append(
                        f"task {task.id}: still {state} after the run "
                        "drained (lost in flight)"
                    )
                elif state == "nascent" and not failed:
                    violations.append(
                        f"task {task.id}: nascent in a live app after the "
                        "run drained (lost before placement)"
                    )
    for app_id, task_id in dead_ids:
        if (app_id, task_id) not in seen_dead:
            violations.append(
                f"dead-letter record for {task_id} (app {app_id}) but "
                "task not DEAD"
            )
    violations.extend(scheduler.placement_violations)
    return violations


def audit_meter(meter, at_end: bool = True) -> List[str]:
    """Billing consistency: host busy intervals well-formed (closed when
    the run has drained, non-negative, chronologically ordered,
    non-overlapping) and scheduling turnovers non-negative — the
    invariants ``cumulative_instance_hours`` (the billing figure) rests
    on.  Chaos can legally reshape intervals (aborts close them early,
    recoveries reopen), but can never corrupt them."""
    violations: List[str] = []
    for host, intervals in meter._host_intervals.items():
        prev_end = None
        for iv in intervals:
            if len(iv) == 1:
                if at_end:
                    violations.append(
                        f"{host.id}: busy interval opened at {iv[0]:.6g} "
                        "never closed"
                    )
                continue
            start, end = iv
            if end < start:
                violations.append(
                    f"{host.id}: negative busy interval [{start:.6g}, {end:.6g}]"
                )
            if prev_end is not None and start < prev_end:
                violations.append(
                    f"{host.id}: overlapping busy intervals at {start:.6g}"
                )
            prev_end = end
    for t in meter._sched_turnovers:
        if t < 0:
            violations.append(f"negative scheduling turnover {t:.6g}")
            break
    # Rework accounting (spot survival): wasted task-seconds of aborted
    # executions.  Per-TASK time, so concurrency can legitimately push it
    # past the busy-interval wall clock (intervals merge co-resident
    # tasks) — but it can never be negative, and a world with no aborts
    # must bill zero rework.
    rework = getattr(meter, "rework_seconds", 0.0)
    if rework < 0:
        violations.append(f"negative rework accounting {rework:.6g}")
    return violations


def audit_serve(driver) -> List[str]:
    """Serve-layer conservation law (round 9 — the multi-tenant chaos
    soak's referee).  After a drained ``ServeDriver.run``:

      * capacity fully settled: zero in-flight, empty spill buffer,
        empty admission ledger;
      * globally and per tier, ``admitted == completed + failed_jobs +
        preempted`` — every admission terminates exactly once (a
        preemption *is* a termination of that admission; the victim's
        re-entry is a fresh ``admitted`` when the spill buffer
        readmits it);
      * every preempted job was requeued-to-spill exactly once
        (``preempted == preempt_requeued``), so with the spill buffer
        empty each victim re-entered and then terminated — nothing
        vanished, nothing terminated twice;
      * every surviving (non-abandoned) session's world passes the
        task-conservation, cluster-state, and billing audits.

    Returns human-readable violations (empty = the law holds).
    """
    violations: List[str] = []
    q = driver.queue
    if q.in_flight != 0:
        violations.append(
            f"admission queue drained with in_flight={q.in_flight}"
        )
    if q.spilled:
        violations.append(
            f"{len(q.spilled)} arrival(s) left in the spill buffer"
        )
    if driver._inflight:
        violations.append(
            f"{len(driver._inflight)} stale admission ledger entries"
        )
    # DRF tenant fairness (round 17): with a tenant quota on, every
    # admission charged its tenant's dominant-share occupancy and every
    # settlement must have given exactly that share back — a drained
    # service's per-(tier, tenant) ledger is zero.  A positive residue
    # is a leaked release (that tenant is permanently over-charged and
    # will be quota-shed forever); a negative one is a double release.
    if getattr(q, "tenant_quota", None) is not None:
        for (tier, tenant), occ in sorted(q.tenant_occupancy.items()):
            if abs(occ) > 1e-6:
                violations.append(
                    f"tenant {tenant!r} tier {tier}: dominant-share "
                    f"occupancy residue {occ:.6g} after drain "
                    "(leaked or double-released quota charge)"
                )

    def _check(counters, scope: str) -> None:
        admitted = counters.get("admitted", 0)
        settled = (
            counters.get("completed", 0)
            + counters.get("failed_jobs", 0)
            + counters.get("preempted", 0)
        )
        if admitted != settled:
            violations.append(
                f"{scope}: admitted {admitted} != completed + failed + "
                f"preempted {settled} (an admission terminated zero or "
                "multiple times)"
            )

    snap = driver.slo.snapshot()
    _check(snap["counters"], "service")
    if snap["counters"].get("preempted", 0) != snap["counters"].get(
        "preempt_requeued", 0
    ):
        violations.append(
            f"preempted {snap['counters'].get('preempted', 0)} != "
            f"preempt_requeued {snap['counters'].get('preempt_requeued', 0)}"
        )
    for tier, tsnap in snap.get("tiers", {}).items():
        _check(tsnap["counters"], f"tier {tier}")
    for s in driver.sessions + driver._retired:
        violations += [
            f"session {s.label}: {v}"
            for v in (
                audit_conservation(s.scheduler, s._injected)
                + audit_cluster(s.cluster)
                + audit_meter(s.meter)
            )
        ]
    return violations


def audit_run(
    scheduler, apps, context: str = "end of run",
    cluster=None, meter=None,
) -> None:
    """One-call referee for a drained (chaos) run: cluster-state,
    conservation, and billing audits; raises :class:`AuditError` with
    every violation on the first breach.  ``cluster``/``meter`` default
    to the scheduler's own."""
    cluster = cluster if cluster is not None else scheduler.cluster
    meter = meter if meter is not None else scheduler.meter
    violations = audit_cluster(cluster)
    violations += audit_conservation(scheduler, apps)
    if meter is not None:
        violations += audit_meter(meter)
    if violations:
        raise AuditError(
            f"simulation state corrupted ({context}):\n  "
            + "\n  ".join(violations)
        )
