"""Simulation-state invariant auditor — the framework's sanitizer.

The reference has **no race detection or sanitizers** (SURVEY.md §5); its
concurrency safety rests on SimPy's cooperative scheduling plus a mutex.
This framework's cooperative kernel gives the same atomicity, but resource
accounting bugs (double release, leaked admission, negative capacity,
ghost tasks on dead hosts) would corrupt results *silently* — placements
still happen, metrics still print.  The auditor makes the invariants
explicit and checkable at any dispatch point:

  * per host, per dimension: ``0 ≤ available ≤ total`` (up hosts);
  * the sum of resident tasks' demands equals the capacity in use;
  * down hosts hold no tasks (tasks whose abort has fired but not yet
    been delivered are tolerated — a legitimate transient between the
    failure event and the aborted process resuming);
  * down hosts report the −1 availability sentinel in
    ``availability_matrix`` (what keeps fit masks off them);
  * a Python-backend route is busy iff it has a transfer in service
    (native routes keep their queue in the C++ engine and are skipped).

Run it ad hoc (``violations = audit_cluster(cluster)``), or install it as
a kernel step observer (``start_periodic_audit``) to fail fast at the
first corrupted state — the DES analog of running under a sanitizer.
The observer never schedules events, so it cannot advance sim time or
change any metric.
"""

from __future__ import annotations

from typing import List

__all__ = ["AuditError", "audit_cluster", "check", "start_periodic_audit"]

#: Relative tolerance for float accounting (fractional trace demands
#: accumulate rounding on acquire/release).
_RTOL = 1e-6


class AuditError(AssertionError):
    """Raised by the periodic auditor on the first invariant violation."""


def _close(a: float, b: float, scale: float) -> bool:
    return abs(a - b) <= _RTOL * max(scale, 1.0)


def check(cluster, context: str) -> None:
    """Audit and raise :class:`AuditError` (with ``context`` in the
    message) on any violation — the single raise path shared by the
    periodic observer and end-of-run checks."""
    violations = audit_cluster(cluster)
    if violations:
        raise AuditError(
            f"simulation state corrupted ({context}):\n  "
            + "\n  ".join(violations)
        )


def audit_cluster(cluster) -> List[str]:
    """Check every invariant; return human-readable violations (empty = ok)."""
    from pivot_tpu.infra.network import NativeRoute

    violations: List[str] = []
    dims = ("cpus", "mem", "disk", "gpus")
    avail_mat = None
    for hi, host in enumerate(cluster.hosts):
        r = host.resource
        if not host.up:
            # In-flight completions that legitimately outlive the crash:
            # process executor — aborts already triggered in Host._aborts;
            # fast executor — due-completion tie-breaks kept resident by
            # abort_host for their one-hop conclusion (executor.py).
            fast_live = (
                {t for t, _staged in cluster.executor.resident(host)}
                if cluster.executor is not None
                else set()
            )
            stuck = [
                t for t in host._tasks
                if t not in fast_live
                and not (t in host._aborts and host._aborts[t].triggered)
            ]
            if stuck:
                violations.append(
                    f"{host.id}: down but holds {len(stuck)} task(s) with "
                    "no abort in flight"
                )
            if avail_mat is None:
                avail_mat = cluster.availability_matrix()
            if not (avail_mat[hi] == -1.0).all():
                violations.append(
                    f"{host.id}: down but availability row is "
                    f"{avail_mat[hi].tolist()}, not the -1 sentinel"
                )
            continue
        in_use = [0.0, 0.0, 0.0, 0.0]
        for task in host._tasks:
            g = task.group
            for i, d in enumerate((g.cpus, g.mem, g.disk, g.gpus)):
                in_use[i] += d
        for i, dim in enumerate(dims):
            avail = getattr(r, dim)
            total = getattr(r, "t_" + dim)
            if avail < -_RTOL * max(total, 1.0):
                violations.append(f"{host.id}: negative {dim} ({avail})")
            if avail > total * (1 + _RTOL):
                violations.append(
                    f"{host.id}: {dim} available {avail} exceeds total {total}"
                )
            if not _close(total - avail, in_use[i], total):
                violations.append(
                    f"{host.id}: {dim} in use {total - avail:.6g} != "
                    f"Σ resident demands {in_use[i]:.6g}"
                )
    for key, route in cluster._routes.items():
        if isinstance(route, NativeRoute):
            continue  # queue state lives in the C++ engine
        if route._busy != (route._in_service is not None):
            violations.append(
                f"route {key}: busy={route._busy} but "
                f"in_service={route._in_service!r}"
            )
    return violations


def start_periodic_audit(cluster, period: float = 5.0) -> None:
    """Audit at event boundaries, at most once per ``period`` sim-seconds;
    raise :class:`AuditError` with the full violation list the first time
    any invariant breaks.

    Installed as a kernel step observer (``Environment.add_step_observer``)
    rather than as heap events: the audit piggybacks on real events, so it
    cannot advance sim time past the last workload event, keep ``run()``
    alive, or perturb event ordering."""
    env = cluster.env
    last = [env.now]

    def _observe():
        if env.now - last[0] < period:
            return
        last[0] = env.now
        check(cluster, f"t={env.now:.3f}")

    env.add_step_observer(_observe)
